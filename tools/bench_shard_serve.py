"""Sharded & disaggregated serving bench — the committed artifact (DESIGN.md §25).

Produces ``--out-dir`` (default ``bench_results/shard_serve_cpu/``) with the
three documents the subsystem is judged by:

- ``shard_serve.json`` — (a) a 2-chip TP replica (``tp=2`` serve mesh over
  virtual CPU devices) driven by the SAME seeded workload as a single-chip
  oracle: ``token_identical`` must be 1.0, the trace-count pins must hold
  under the mesh, and measured params+KV bytes per chip must be at most
  single-chip / 1.8 (GSPMD actually sharded the planes; nothing silently
  replicated). Plus (b)'s summaries and the trace segment table separating
  prefill-tier / handoff / decode wall.
- ``tiered.jsonl`` — the telemetry stream of a real prefill-tier/decode-tier
  fleet run (render: ``python tools/telemetry_report.py tiered.jsonl``):
  every completion CRC-verified over the framed handoff wire
  (``handoff_failures == 0``), and a second leg that kills the prefill
  replica mid-run and still loses zero requests (the no_disagg fallback).
- ``plan_serve.json`` — the serving scenario planner's candidate table with
  real measured tokens/s for the top predictions; the gate is that the
  picked mesh IS the measured-best candidate.

Without ``--checkpoint`` the tool first trains the pixel LM on the committed
MNIST IDX fixture (the spec/quant A/B recipe) so the artifact reflects a
trained model. ``--quick`` shrinks training and load for the CI smoke job.

Usage::

    python tools/bench_shard_serve.py --out-dir bench_results/shard_serve_cpu
    python tools/bench_shard_serve.py --quick --out-dir /tmp/sss --work-dir /tmp/ssw
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

# The TP legs need multiple chips; on CPU that is the host-platform device
# split, which must be set before jax initializes.
_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count=8"
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = \
        (os.environ.get("XLA_FLAGS", "") + " " + _DEVCOUNT_FLAG).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(_REPO, "tests", "fixtures", "mnist_idx")


def ensure_checkpoint(args) -> str:
    """``--checkpoint`` verbatim, else train the default pixel LM on the
    committed MNIST fixture and return the saved TrainState path."""
    if args.checkpoint:
        return args.checkpoint
    cached = os.path.join(args.work_dir, "model_lm.ckpt")
    if os.path.exists(cached):
        print(f"reusing trained checkpoint {cached}")
        return cached
    from csed_514_project_distributed_training_using_pytorch_tpu.train import (
        lm as lm_train,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.config import (
        LMConfig,
    )

    os.makedirs(args.work_dir, exist_ok=True)
    cfg = LMConfig(epochs=args.train_epochs, batch_size=32, eval_batch=50,
                   data_dir=args.data_dir, generate=0,
                   results_dir=args.work_dir,
                   images_dir=os.path.join(args.work_dir, "images"))
    print(f"training checkpoint: {args.train_epochs} epochs on {args.data_dir}")
    lm_train.main(cfg)
    return os.path.join(args.work_dir, "model_lm.ckpt")


def _workload(model, n, max_new, seed):
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        Request,
    )

    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.integers(0, 96))
        reqs.append(Request(
            prompt=rng.integers(0, model.vocab_size - 1,
                                size=plen).astype(np.int32),
            max_new_tokens=int(rng.integers(max(1, max_new // 2), max_new + 1)),
            request_id=i))
    return reqs


def _run(engine, reqs):
    t0 = time.monotonic()
    comps = engine.run(reqs)
    wall = time.monotonic() - t0
    toks = {c.request.request_id: np.asarray(c.tokens, np.int32)
            for c in comps}
    return toks, wall


def run_shard_identity(model, params, args) -> dict:
    """Part (a): 2-chip TP replica vs the single-chip oracle — token identity,
    trace pins, and the measured per-chip byte gate."""
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        shard as shard_mod,
    )

    reqs = _workload(model, args.requests, args.max_new_tokens, args.seed)
    print(f"== shard identity: {len(reqs)} requests, "
          f"single chip vs {args.shard}")

    oracle = ContinuousBatchingEngine(model, params, num_slots=args.num_slots)
    want, wall_1 = _run(oracle, reqs)
    acct_1 = oracle.byte_accounting()

    tp, dp = shard_mod.parse_shard_spec(args.shard)
    sm = shard_mod.build_serve_mesh(tp=tp, dp=dp)
    sharded = ContinuousBatchingEngine(model, params,
                                       num_slots=args.num_slots, mesh=sm)
    got, wall_n = _run(sharded, [r for r in reqs])
    acct_n = sharded.byte_accounting()

    matched = sum(int(np.array_equal(want[i], got[i])) for i in want)
    identical = matched / len(want)
    single_pk = acct_1["params_bytes"] + acct_1["kv_bytes_resident"]
    per_chip_pk = acct_n["params_kv_bytes_per_chip_max"]
    ratio = per_chip_pk / single_pk
    pins_ok = (sharded.trace_count <= 1
               and sharded.trace_count == oracle.trace_count
               and sharded.admit_trace_count == 1
               and all(v <= 1 for v in sharded.prefill_trace_counts.values()))
    doc = {
        "shard": acct_n["mesh"],
        "requests": len(reqs),
        "token_identical": identical,
        "trace_pins_ok": pins_ok,
        "decode_compilations": sharded.trace_count,
        "prefill_compilations": dict(sharded.prefill_trace_counts),
        "single_chip": {"params_bytes": acct_1["params_bytes"],
                        "kv_bytes_resident": acct_1["kv_bytes_resident"],
                        "params_kv_bytes": single_pk,
                        "wall_s": wall_1},
        "per_chip": {str(k): v for k, v in acct_n["per_chip"].items()},
        "params_kv_bytes_per_chip_max": per_chip_pk,
        "per_chip_over_single_ratio": ratio,
        "byte_gate": f"per-chip params+KV <= single-chip / 1.8 "
                     f"(measured ratio {ratio:.4f})",
        "sharded_wall_s": wall_n,
    }
    print(f"   token identity {matched}/{len(want)}, per-chip params+KV "
          f"ratio {ratio:.4f} (gate <= {1 / 1.8:.4f}), trace pins "
          f"{'OK' if pins_ok else 'BROKEN'}")
    if identical != 1.0:
        raise SystemExit("sharded tokens diverged from the single-chip oracle")
    if ratio > 1 / 1.8:
        raise SystemExit(f"per-chip byte ratio {ratio:.4f} > 1/1.8 — "
                         "sharding did not reduce residency")
    if not pins_ok:
        raise SystemExit("trace-count pins broke under the mesh")
    return doc


def run_tier_leg(args, loadgen, ckpt, *, name, telemetry, kill=False) -> dict:
    """One tiered-fleet run through serve_loadgen; returns its summary doc."""
    out = os.path.join(args.work_dir, f"{name}_summary.json")
    trace_dir = os.path.join(args.work_dir, f"{name}_trace")
    argv = ["--replicas", "2", "--tiers", "prefill:1,decode:1",
            "--checkpoint", ckpt, "--seed", str(args.seed),
            "--num-slots", str(args.num_slots),
            "--requests", str(args.requests),
            "--max-new-tokens", str(args.max_new_tokens),
            "--prompt-lens", "8,32,64", "--mode", "closed",
            "--concurrency", "4", "--max-restarts", "3",
            "--heartbeat-timeout-s", "60",
            "--telemetry", telemetry, "--trace-dir", trace_dir,
            "--summary-json", out]
    label = "kill prefill replica mid-run" if kill else "clean"
    print(f"== tiered fleet ({label}): prefill:1,decode:1, "
          f"{args.requests} requests")
    old = os.environ.pop("RESILIENCE_FAULTS", None)
    try:
        if kill:
            os.environ["RESILIENCE_FAULTS"] = f"kill:proc=0,step={args.kill_step}"
        rc = loadgen.main(argv)
    finally:
        os.environ.pop("RESILIENCE_FAULTS", None)
        if old is not None:
            os.environ["RESILIENCE_FAULTS"] = old
    if rc != 0:
        raise SystemExit(f"tiered fleet leg ({name}) failed with rc {rc}")
    with open(out) as f:
        summ = json.load(f)
    if summ["ok"] != args.requests or summ.get("failed"):
        raise SystemExit(f"tiered leg ({name}): "
                         f"{summ['ok']}/{args.requests} ok — requests lost")
    if not kill and (summ.get("handoff_failures") or 0):
        raise SystemExit(f"clean tiered leg had "
                         f"{summ['handoff_failures']} handoff failures")
    summ["_trace_dir"] = trace_dir
    return summ


def trace_segment_table(trace_dir) -> dict:
    """Reduce a tiered run's spans to the per-segment wall table — the gate
    is that prefill_tier / handoff / decode are separated, exclusively."""
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.trace import (
        read_spans,
        summarize_traces,
    )

    spans, _ = read_spans([trace_dir])
    summary = summarize_traces(spans)
    seg = summary["segments"]
    table = {name: {"p50_s": row.get("p50"), "total_s": row.get("total")}
             for name, row in seg.items()
             if (row.get("total") or 0) > 0}
    print("   trace segments (p50):")
    for name in ("prefill_tier", "handoff", "decode_first", "decode_tail"):
        row = seg.get(name) or {}
        print(f"     {name:>14}  {((row.get('p50') or 0)) * 1e3:8.2f} ms")
    return {"traces": summary["traces"], "segments": table}


def run_plan_serve(model, args) -> dict:
    """Part (c): the serving scenario planner with REAL measurement — the
    committed gate is pick == measured-best."""
    import jax

    from csed_514_project_distributed_training_using_pytorch_tpu.plan import (
        Topology,
        search_serve,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.plan.scenarios import (
        for_serve,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        shard as shard_mod,
    )

    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, model.seq_len), np.int32))["params"]
    n_meas = args.measure_requests

    def measure(tp, dp):
        sm = shard_mod.build_serve_mesh(tp=tp, dp=dp)
        engine = ContinuousBatchingEngine(model, params,
                                          num_slots=args.num_slots,
                                          mesh=(None if tp == dp == 1 else sm))
        reqs = _workload(model, n_meas, args.max_new_tokens, args.seed + 31)
        engine.run(reqs[:1])        # compile outside the measured window
        toks, wall = _run(engine, reqs)
        new = sum(len(t) for t in toks.values())
        print(f"   measured tp={tp},dp={dp}: {new / wall:.1f} tokens/s")
        return new / wall

    topo = Topology(num_devices=4, device_kind="cpu", hbm_bytes=16 << 30)
    sc = for_serve(model, num_slots=args.num_slots, prompt_len=64, topo=topo,
                   measure=measure)
    print(f"== plan serve: {topo.num_devices} devices, "
          f"{args.num_slots} slots, measure top {args.measure_top}")
    rows = search_serve(sc, measure_top=args.measure_top)
    measured = [r for r in rows if r.measured_tokens_per_s is not None]
    best = max(measured, key=lambda r: r.measured_tokens_per_s)
    pick_is_best = rows[0] is best
    doc = {
        "metric": "serving scenario planner (predict -> prune -> measure)",
        "topology": {"num_devices": topo.num_devices, "device_kind": "cpu",
                     "hbm_bytes": topo.hbm_bytes},
        "num_slots": args.num_slots,
        "prompt_len": 64,
        "candidates": [
            {"shard": r.shard_spec(), "tp": r.tp, "dp": r.dp,
             "predicted_tokens_per_s": r.costs.tokens_per_s,
             "params_bytes_per_chip": r.costs.params_bytes_per_chip,
             "kv_bytes_per_chip": r.costs.kv_bytes_per_chip,
             "slots_at_budget": r.costs.slots_at_budget,
             "fits": r.costs.fits,
             "measured_tokens_per_s": r.measured_tokens_per_s}
            for r in rows],
        "picked": rows[0].shard_spec(),
        "measured_best": best.shard_spec(),
        "pick_is_measured_best": pick_is_best,
    }
    print(f"   picked {doc['picked']} "
          f"({rows[0].measured_tokens_per_s:.1f} tokens/s measured); "
          f"measured-best {doc['measured_best']}")
    if not pick_is_best:
        raise SystemExit("planner pick is not the measured-best candidate")
    return doc


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--checkpoint", default="",
                   help="trained train.lm TrainState/params (default: train "
                        "one on the committed MNIST fixture first)")
    p.add_argument("--train-epochs", type=int, default=12)
    p.add_argument("--data-dir", default=_FIXTURE)
    p.add_argument("--work-dir", default="/tmp/shard_serve_work",
                   help="scratch dir for the checkpoint, traces + summaries")
    p.add_argument("--out-dir", default="bench_results/shard_serve_cpu")
    p.add_argument("--shard", default="tp=2",
                   help="the sharded replica's mesh for the identity leg")
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--measure-top", type=int, default=3)
    p.add_argument("--measure-requests", type=int, default=6)
    p.add_argument("--kill-step", type=int, default=3,
                   help="RESILIENCE_FAULTS step for the prefill-kill leg")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quick", action="store_true",
                   help="CI smoke sizing: tiny training + load")
    args = p.parse_args(argv)
    if args.quick:
        args.train_epochs = min(args.train_epochs, 2)
        args.requests = min(args.requests, 8)
        args.max_new_tokens = min(args.max_new_tokens, 12)
        args.measure_top = min(args.measure_top, 2)
        args.measure_requests = min(args.measure_requests, 3)

    os.makedirs(args.out_dir, exist_ok=True)
    os.makedirs(args.work_dir, exist_ok=True)

    spec_mod = importlib.util.spec_from_file_location(
        "serve_loadgen", os.path.join(_REPO, "tools", "serve_loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec_mod)
    spec_mod.loader.exec_module(loadgen)

    import jax

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        checkpoint,
    )

    ckpt = ensure_checkpoint(args)
    model = lm.TransformerLM()          # the train.lm default pixel LM
    import jax.numpy as jnp

    init = model.init({"params": jax.random.PRNGKey(0)},
                      jnp.zeros((1, model.seq_len), jnp.int32))["params"]
    params = checkpoint.load_params_or_state(ckpt, init)

    shard_doc = run_shard_identity(model, params, args)

    telemetry = os.path.join(args.out_dir, "tiered.jsonl")
    clean = run_tier_leg(args, loadgen, ckpt, name="tiered",
                         telemetry=telemetry)
    segments = trace_segment_table(clean.pop("_trace_dir"))
    kill = run_tier_leg(args, loadgen, ckpt, name="tiered_kill",
                        telemetry=os.path.join(args.work_dir,
                                               "tiered_kill.jsonl"),
                        kill=True)
    kill.pop("_trace_dir", None)
    print(f"   clean: {clean['handoffs']} handoffs "
          f"({clean['handoff_bytes']} B, {clean['handoff_failures']} failed); "
          f"kill: {kill['ok']}/{args.requests} ok after "
          f"{sum(r.get('restarts', 0) for r in kill.get('per_replica', []))} "
          f"restart(s)")

    plan_doc = run_plan_serve(model, args)
    with open(os.path.join(args.out_dir, "plan_serve.json"), "w") as f:
        json.dump(plan_doc, f, indent=1)

    doc = {
        "metric": "sharded + disaggregated serving (DESIGN.md §25)",
        "checkpoint": ckpt,
        "trained_epochs": None if args.checkpoint else args.train_epochs,
        "quick": args.quick,
        "shard_identity": shard_doc,
        "tiered_fleet": {
            "clean": clean,
            "prefill_kill": kill,
            "zero_lost_under_kill": kill["ok"] == args.requests,
            "trace": segments,
        },
        "plan_serve": {"picked": plan_doc["picked"],
                       "pick_is_measured_best":
                           plan_doc["pick_is_measured_best"],
                       "file": "plan_serve.json"},
        "gates": {
            "token_identical": shard_doc["token_identical"] == 1.0,
            "per_chip_bytes_le_single_over_1p8":
                shard_doc["per_chip_over_single_ratio"] <= 1 / 1.8,
            "trace_pins_ok": shard_doc["trace_pins_ok"],
            "handoffs_crc_verified_zero_failures":
                (clean.get("handoff_failures") or 0) == 0,
            "zero_requests_lost_under_prefill_kill":
                kill["ok"] == args.requests,
            "plan_pick_is_measured_best": plan_doc["pick_is_measured_best"],
        },
    }
    out = os.path.join(args.out_dir, "shard_serve.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    ok = all(doc["gates"].values())
    print(f"gates: {doc['gates']}")
    print(f"wrote {out}, {telemetry}, "
          f"{os.path.join(args.out_dir, 'plan_serve.json')}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
