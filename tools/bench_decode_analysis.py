"""Where does small-config decode time go? — the r4 verdict item 6 analysis.

``bench_lm.py``'s d=256 decode sits at 19-44% of the HBM roofline where the d=1024
config hits 92%. The chained two-point protocol already cancels the tunnel's ~70 ms
HOST dispatch tax, so whatever remains is on-device. This tool decomposes it:

1. ``t_token`` — measured per-token seconds (chained protocol over full
   ``generate`` calls, exactly bench_lm's measurement);
2. ``t_roofline`` — the HBM bound for one token (cache re-read + amortized
   weights, bench_lm's accounting);
3. ``ops_per_token`` — executable-op count of ONE compiled decode step, read from
   the optimized HLO of ``jax.jit(decode_step).lower(...).compile()`` (fusions,
   copies, custom calls — everything the TensorCore sequencer must launch);
4. ``per_op_overhead_s = (t_token - t_roofline) / ops_per_token``.

If the per-op overhead lands at the TPU's known fixed per-kernel cost (~1-5 µs),
the residual is the DEVICE's per-op launch floor at a model size whose math is
microseconds — an op-count problem (fusing the step), not a bandwidth or tunnel
problem. The committed artifact makes that attribution explicit.

Usage: ``python tools/bench_decode_analysis.py [--d-model 256 ...]`` — ONE JSON
line; CPU-drivable at tiny shapes (the op count is platform-specific, so the
committed artifact must come from a TPU run).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

# Script-mode import path: ``python tools/bench_decode_analysis.py`` puts tools/
# on sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=16)
    p.add_argument("--seq", type=int, default=784)
    p.add_argument("--gen-batch", type=int, default=8)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from csed_514_project_distributed_training_using_pytorch_tpu.models import lm as lm_mod
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        chained_diff_time, peak_hbm_bytes,
    )

    model = lm_mod.TransformerLM(
        vocab_size=args.vocab + 1, seq_len=args.seq, embed_dim=args.d_model,
        num_layers=args.layers, num_heads=args.heads,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, args.seq), jnp.int32))["params"]

    # --- 3. ops per token: the optimized HLO of ONE decode step ---------------
    cache = lm_mod.init_cache(model, args.gen_batch)
    tok = jnp.zeros((args.gen_batch,), jnp.int32)

    def one_step(params, cache, tok):
        cache, logp = lm_mod.decode_step(model, params, cache, tok,
                                         jnp.int32(0), prefix_len=128)
        return cache, logp

    compiled = jax.jit(one_step).lower(params, cache, tok).compile()
    hlo = compiled.as_text()
    # Executable ops = instructions in ENTRY whose opcode launches work on the
    # TensorCore: fusions, custom-calls, copies, convolutions/dots that escaped
    # fusion. Parameter/tuple plumbing is free.
    entry = hlo.split("ENTRY")[-1]
    launched = re.findall(
        r"= \S+ (fusion|custom-call|copy|convolution|dot|all-reduce|"
        r"dynamic-slice|dynamic-update-slice|reduce|transpose|select-and-scatter)",
        entry)
    ops_per_token = len(launched)
    op_kinds = {}
    for kind in launched:
        op_kinds[kind] = op_kinds.get(kind, 0) + 1

    # --- 1. measured per-token seconds (bench_lm's protocol) ------------------
    def gen_chain(n):
        def body(k, _):
            ids = lm_mod.generate(model, params, k, batch=args.gen_batch,
                                  temperature=1.0)
            return jax.random.fold_in(k, jnp.sum(ids)), ()

        def run(k):
            return lax.scan(body, k, None, length=n)[0]

        return jax.jit(run)

    def synced(n):
        compiled = gen_chain(n)
        return lambda: jax.device_get(compiled(jax.random.PRNGKey(3)))

    per_gen, (n1, t1), (n2, t2), converged = chained_diff_time(
        synced, n1=1, grow=4, max_n=64)
    t_token = per_gen / args.seq

    # --- 2. HBM roofline per token (bench_lm's accounting) --------------------
    e, s = args.d_model, args.seq
    hd = e // args.heads
    itemsize = jnp.dtype(model.dtype).itemsize
    # average static prefix read per step under the segmented scan
    seg = lm_mod.DECODE_SEGMENT
    nseg = -(-s // seg)
    avg_prefix = sum(min((j + 1) * seg, s) * seg for j in range(nseg)) / s
    cache_bytes = args.layers * 2 * args.heads * hd * itemsize * avg_prefix
    weight_bytes = (args.layers * 12 * e * e + 2 * e * (args.vocab + 1)) * itemsize
    bytes_per_token = cache_bytes + weight_bytes / args.gen_batch
    dev = jax.devices()[0]
    hbm = (peak_hbm_bytes(getattr(dev, "device_kind", ""))
           if dev.platform == "tpu" else None)
    t_roofline = (args.gen_batch * bytes_per_token / hbm) if hbm else None

    residual = (t_token - t_roofline) if t_roofline else None
    doc = {
        "metric": "LM decode per-token decomposition (d=%d)" % args.d_model,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "d_model": args.d_model, "layers": args.layers, "heads": args.heads,
        "seq": s, "decode_batch": args.gen_batch,
        "tokens_per_s": round(args.gen_batch * s / per_gen, 1),
        "t_token_s": t_token, "chain_converged": converged,
        "ops_per_token": ops_per_token, "op_kinds": op_kinds,
        "t_roofline_s": t_roofline,
        "hbm_roofline_frac": (round(t_roofline / t_token, 4)
                              if t_roofline else None),
        "residual_s": residual,
        "per_op_overhead_us": (round(1e6 * residual / ops_per_token, 3)
                               if residual is not None else None),
        "attribution": ("residual / ops_per_token is the device's per-op launch "
                        "floor; the tunnel's ~70 ms host tax is cancelled by the "
                        "chained two-point protocol"),
    }
    print(json.dumps(doc))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
