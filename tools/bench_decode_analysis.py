"""Where does small-config decode time go? — the r4 verdict item 6 analysis.

``bench_lm.py``'s d=256 decode sits at 19-44% of the HBM roofline where the d=1024
config hits 92%. The chained two-point protocol already cancels the tunnel's ~70 ms
HOST dispatch tax, so whatever remains is on-device. This tool decomposes it:

1. ``t_token`` — measured per-token seconds (chained protocol over full
   ``generate`` calls, exactly bench_lm's measurement);
2. ``t_roofline`` — the HBM bound for one token (cache re-read + amortized
   weights, bench_lm's accounting);
3. ``ops_per_token`` — executable-op count of ONE compiled decode step, read from
   the optimized HLO of ``jax.jit(decode_step).lower(...).compile()`` (fusions,
   copies, custom calls — everything the TensorCore sequencer must launch);
4. ``per_op_overhead_s = (t_token - t_roofline) / ops_per_token``.

If the per-op overhead lands at the TPU's known fixed per-kernel cost (~1-5 µs),
the residual is the DEVICE's per-op launch floor at a model size whose math is
microseconds — an op-count problem (fusing the step), not a bandwidth or tunnel
problem. The committed artifact makes that attribution explicit.

``--ttft-curve`` adds the serving-side decomposition this tool exists to make
explicit post-prefill: the TTFT-vs-prompt-length curve of the continuous-batching
engine with chunked batched prefill ON vs OFF (prefill-as-decode), plus the
prefill-vs-decode wall-clock split of the ON path. Off pays P sequential decode
invocations before the first generated token; on pays ``ceil(P/chunk)`` wide
forwards — the curve is the before/after record of that schedule change.

Usage: ``python tools/bench_decode_analysis.py [--d-model 256 ...]`` — ONE JSON
line; CPU-drivable at tiny shapes (the op count is platform-specific, so the
committed artifact must come from a TPU run).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

# Script-mode import path: ``python tools/bench_decode_analysis.py`` puts tools/
# on sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def ttft_curve(model, params, args) -> list[dict]:
    """TTFT vs prompt length, chunked prefill ON vs OFF, one row per length.

    Each mode reuses ONE engine across the whole curve (slot recycling), with a
    max-length warmup request first, so every chunk size and the decode program
    are compiled before anything is timed — the curve measures the schedule, not
    XLA. The ON rows also split the request wall into prefill (chunk programs)
    vs decode (token steps)."""
    import time as _time

    import numpy as np

    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine, Request,
    )

    lens = [int(x) for x in args.curve_prompt_lens.split(",") if x]
    lens = [l for l in lens if 0 < l < args.seq] or [args.seq // 2]
    chunks = tuple(int(x) for x in args.curve_chunks.split(",") if x)
    rng = np.random.default_rng(0)
    prompts = {p_len: rng.integers(0, args.vocab, size=p_len).astype(np.int32)
               for p_len in lens}
    warm = rng.integers(0, args.vocab, size=max(lens)).astype(np.int32)

    def measure(chunk_sizes):
        eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                       prefill_chunk_sizes=chunk_sizes)
        # Warm ONE request per configured size (a length-c prompt plans as
        # exactly one c-chunk) plus a full-length one — a single max-length
        # warmup would never compile the sizes its greedy plan skips, and the
        # first short measured row would then time XLA instead of the schedule.
        for c in eng.prefill_chunk_sizes:
            eng.run([Request(prompt=warm[:min(c, args.seq - 1)],
                             max_new_tokens=1)])
        eng.run([Request(prompt=warm, max_new_tokens=2)])
        eng.reset_stats()
        rows = {}
        for p_len in lens:
            pre0, inv0 = eng.prefill_wall_s, eng.prefill_invocations
            t0 = _time.monotonic()
            comp = eng.run([Request(prompt=prompts[p_len],
                                    max_new_tokens=args.curve_new_tokens)])[0]
            wall = _time.monotonic() - t0
            prefill_s = eng.prefill_wall_s - pre0
            rows[p_len] = {
                "ttft_s": comp.ttft_s, "wall_s": wall,
                "prefill_wall_s": prefill_s,
                "decode_wall_s": wall - prefill_s,
                "prefill_invocations": eng.prefill_invocations - inv0,
            }
        return rows

    on, off = measure(chunks), measure(())
    return [{
        "prompt_len": p_len,
        "ttft_prefill_s": on[p_len]["ttft_s"],
        "ttft_decode_s": off[p_len]["ttft_s"],
        "ttft_speedup": (off[p_len]["ttft_s"] / on[p_len]["ttft_s"]
                         if on[p_len]["ttft_s"] else None),
        "prefill_invocations": on[p_len]["prefill_invocations"],
        "on_prefill_wall_s": on[p_len]["prefill_wall_s"],
        "on_decode_wall_s": on[p_len]["decode_wall_s"],
        "off_wall_s": off[p_len]["wall_s"],
    } for p_len in lens]


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=16)
    p.add_argument("--seq", type=int, default=784)
    p.add_argument("--gen-batch", type=int, default=8)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--ttft-curve", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="add the serving TTFT-vs-prompt-length curve, chunked "
                        "prefill on vs off, with the prefill/decode wall split")
    p.add_argument("--curve-prompt-lens", default="64,256,512,768",
                   help="prompt lengths for --ttft-curve (clipped to < --seq)")
    p.add_argument("--curve-chunks", default="32,128,512",
                   help="prefill chunk-size set for the ON side of the curve")
    p.add_argument("--curve-new-tokens", type=int, default=8)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from csed_514_project_distributed_training_using_pytorch_tpu.models import lm as lm_mod
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        chained_diff_time, peak_hbm_bytes,
    )

    model = lm_mod.TransformerLM(
        vocab_size=args.vocab + 1, seq_len=args.seq, embed_dim=args.d_model,
        num_layers=args.layers, num_heads=args.heads,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, args.seq), jnp.int32))["params"]

    # --- 3. ops per token: the optimized HLO of ONE decode step ---------------
    cache = lm_mod.init_cache(model, args.gen_batch)
    tok = jnp.zeros((args.gen_batch,), jnp.int32)

    def one_step(params, cache, tok):
        cache, logp = lm_mod.decode_step(model, params, cache, tok,
                                         jnp.int32(0), prefix_len=128)
        return cache, logp

    compiled = jax.jit(one_step).lower(params, cache, tok).compile()
    hlo = compiled.as_text()
    # Executable ops = instructions in ENTRY whose opcode launches work on the
    # TensorCore: fusions, custom-calls, copies, convolutions/dots that escaped
    # fusion. Parameter/tuple plumbing is free.
    entry = hlo.split("ENTRY")[-1]
    launched = re.findall(
        r"= \S+ (fusion|custom-call|copy|convolution|dot|all-reduce|"
        r"dynamic-slice|dynamic-update-slice|reduce|transpose|select-and-scatter)",
        entry)
    ops_per_token = len(launched)
    op_kinds = {}
    for kind in launched:
        op_kinds[kind] = op_kinds.get(kind, 0) + 1

    # --- 1. measured per-token seconds (bench_lm's protocol) ------------------
    def gen_chain(n):
        def body(k, _):
            ids = lm_mod.generate(model, params, k, batch=args.gen_batch,
                                  temperature=1.0)
            return jax.random.fold_in(k, jnp.sum(ids)), ()

        def run(k):
            return lax.scan(body, k, None, length=n)[0]

        return jax.jit(run)

    def synced(n):
        compiled = gen_chain(n)
        return lambda: jax.device_get(compiled(jax.random.PRNGKey(3)))

    per_gen, (n1, t1), (n2, t2), converged = chained_diff_time(
        synced, n1=1, grow=4, max_n=64)
    t_token = per_gen / args.seq

    # --- 2. HBM roofline per token (bench_lm's accounting) --------------------
    e, s = args.d_model, args.seq
    hd = e // args.heads
    itemsize = jnp.dtype(model.dtype).itemsize
    # average static prefix read per step under the segmented scan
    seg = lm_mod.DECODE_SEGMENT
    nseg = -(-s // seg)
    avg_prefix = sum(min((j + 1) * seg, s) * seg for j in range(nseg)) / s
    cache_bytes = args.layers * 2 * args.heads * hd * itemsize * avg_prefix
    weight_bytes = (args.layers * 12 * e * e + 2 * e * (args.vocab + 1)) * itemsize
    bytes_per_token = cache_bytes + weight_bytes / args.gen_batch
    dev = jax.devices()[0]
    hbm = (peak_hbm_bytes(getattr(dev, "device_kind", ""))
           if dev.platform == "tpu" else None)
    t_roofline = (args.gen_batch * bytes_per_token / hbm) if hbm else None

    residual = (t_token - t_roofline) if t_roofline else None
    doc = {
        "metric": "LM decode per-token decomposition (d=%d)" % args.d_model,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "d_model": args.d_model, "layers": args.layers, "heads": args.heads,
        "seq": s, "decode_batch": args.gen_batch,
        "tokens_per_s": round(args.gen_batch * s / per_gen, 1),
        "t_token_s": t_token, "chain_converged": converged,
        "ops_per_token": ops_per_token, "op_kinds": op_kinds,
        "t_roofline_s": t_roofline,
        "hbm_roofline_frac": (round(t_roofline / t_token, 4)
                              if t_roofline else None),
        "residual_s": residual,
        "per_op_overhead_us": (round(1e6 * residual / ops_per_token, 3)
                               if residual is not None else None),
        "attribution": ("residual / ops_per_token is the device's per-op launch "
                        "floor; the tunnel's ~70 ms host tax is cancelled by the "
                        "chained two-point protocol"),
    }
    if args.ttft_curve:
        doc["ttft_curve"] = ttft_curve(model, params, args)
    print(json.dumps(doc))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
