"""Where does small-config decode time go? — the r4 verdict item 6 analysis.

``bench_lm.py``'s d=256 decode sits at 19-44% of the HBM roofline where the d=1024
config hits 92%. The chained two-point protocol already cancels the tunnel's ~70 ms
HOST dispatch tax, so whatever remains is on-device. This tool decomposes it:

1. ``t_token`` — measured per-token seconds (chained protocol over full
   ``generate`` calls, exactly bench_lm's measurement);
2. ``t_roofline`` — the HBM bound for one token (cache re-read + amortized
   weights, bench_lm's accounting);
3. ``ops_per_token`` — executable-op count of ONE compiled decode step, read from
   the optimized HLO of ``jax.jit(decode_step).lower(...).compile()`` (fusions,
   copies, custom calls — everything the TensorCore sequencer must launch);
4. ``per_op_overhead_s = (t_token - t_roofline) / ops_per_token``.

If the per-op overhead lands at the TPU's known fixed per-kernel cost (~1-5 µs),
the residual is the DEVICE's per-op launch floor at a model size whose math is
microseconds — an op-count problem (fusing the step), not a bandwidth or tunnel
problem. The committed artifact makes that attribution explicit.

``--ttft-curve`` adds the serving-side decomposition this tool exists to make
explicit post-prefill: the TTFT-vs-prompt-length curve of the continuous-batching
engine with chunked batched prefill ON vs OFF (prefill-as-decode), plus the
prefill-vs-decode wall-clock split of the ON path. Off pays P sequential decode
invocations before the first generated token; on pays ``ceil(P/chunk)`` wide
forwards — the curve is the before/after record of that schedule change.

``--quant-ab`` runs the quantized-execution A/B this tool's roofline accounting
exists to verify: the SAME greedy workload through a fp32-oracle engine (A) and
a quantized engine (B: ``--ab-kv-dtype``/``--ab-quant-policy``), reporting (1)
**measured** decode bytes/token and KV bytes/slot from the live buffers of each
engine (``byte_accounting()`` — int8 planes and their scale planes priced at
their real itemsize, never a dtype assumption), and slots under the same HBM
budget; (2) the ACCURACY BUDGET: greedy token-match rate vs the fp32 oracle and
the teacher-forced NLL delta through the serving decode path (``--checkpoint``
for real weights); (3) the compile pins: the quantized engine must still trace
exactly one decode program and <= 1 prefill program per chunk size. The output
JSON is the committed ``bench_results/`` artifact format.

``--paged-ab`` runs the paged-KV layout A/B (``bench_results/paged_kv_cpu/``):
the SAME mixed short/long greedy workload through a contiguous-oracle engine
and a ``kv_layout="paged"`` engine — token identity (the adapters' bitwise
contract), measured slots-at-HBM-budget from the workload's actual page
reservations, and the long-prompt TTFT/TPOT tails that prove capacity wasn't
bought by taxing full-context requests.

All byte accounting in this tool is **byte-true**: cache and weight bytes are
summed from the actual arrays a run holds (``ops.quant.tree_bytes``), so a
quantized run's roofline denominator shrinks exactly as far as its buffers did.

Usage: ``python tools/bench_decode_analysis.py [--d-model 256 ...]`` — ONE JSON
line; CPU-drivable at tiny shapes (the op count is platform-specific, so the
committed artifact must come from a TPU run).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

# Script-mode import path: ``python tools/bench_decode_analysis.py`` puts tools/
# on sys.path, not the repo root the package lives in.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def ttft_curve(model, params, args) -> list[dict]:
    """TTFT vs prompt length, chunked prefill ON vs OFF, one row per length.

    Each mode reuses ONE engine across the whole curve (slot recycling), with a
    max-length warmup request first, so every chunk size and the decode program
    are compiled before anything is timed — the curve measures the schedule, not
    XLA. The ON rows also split the request wall into prefill (chunk programs)
    vs decode (token steps)."""
    import time as _time

    import numpy as np

    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine, Request,
    )

    lens = [int(x) for x in args.curve_prompt_lens.split(",") if x]
    lens = [l for l in lens if 0 < l < args.seq] or [args.seq // 2]
    chunks = tuple(int(x) for x in args.curve_chunks.split(",") if x)
    rng = np.random.default_rng(0)
    prompts = {p_len: rng.integers(0, args.vocab, size=p_len).astype(np.int32)
               for p_len in lens}
    warm = rng.integers(0, args.vocab, size=max(lens)).astype(np.int32)

    def measure(chunk_sizes):
        eng = ContinuousBatchingEngine(model, params, num_slots=1,
                                       prefill_chunk_sizes=chunk_sizes)
        # Warm ONE request per configured size (a length-c prompt plans as
        # exactly one c-chunk) plus a full-length one — a single max-length
        # warmup would never compile the sizes its greedy plan skips, and the
        # first short measured row would then time XLA instead of the schedule.
        for c in eng.prefill_chunk_sizes:
            eng.run([Request(prompt=warm[:min(c, args.seq - 1)],
                             max_new_tokens=1)])
        eng.run([Request(prompt=warm, max_new_tokens=2)])
        eng.reset_stats()
        rows = {}
        for p_len in lens:
            pre0, inv0 = eng.prefill_wall_s, eng.prefill_invocations
            t0 = _time.monotonic()
            comp = eng.run([Request(prompt=prompts[p_len],
                                    max_new_tokens=args.curve_new_tokens)])[0]
            wall = _time.monotonic() - t0
            prefill_s = eng.prefill_wall_s - pre0
            rows[p_len] = {
                "ttft_s": comp.ttft_s, "wall_s": wall,
                "prefill_wall_s": prefill_s,
                "decode_wall_s": wall - prefill_s,
                "prefill_invocations": eng.prefill_invocations - inv0,
            }
        return rows

    on, off = measure(chunks), measure(())
    return [{
        "prompt_len": p_len,
        "ttft_prefill_s": on[p_len]["ttft_s"],
        "ttft_decode_s": off[p_len]["ttft_s"],
        "ttft_speedup": (off[p_len]["ttft_s"] / on[p_len]["ttft_s"]
                         if on[p_len]["ttft_s"] else None),
        "prefill_invocations": on[p_len]["prefill_invocations"],
        "on_prefill_wall_s": on[p_len]["prefill_wall_s"],
        "on_decode_wall_s": on[p_len]["decode_wall_s"],
        "off_wall_s": off[p_len]["wall_s"],
    } for p_len in lens]


def quant_ab(model, params, args) -> dict:
    """The quantization A/B: one seeded greedy workload through a fp32-oracle
    engine and a quantized engine, returning measured bytes, the accuracy
    budget, and the compile pins — the committed-artifact document.

    Both sides run on an fp32 base model regardless of ``--bf16`` (the main
    decomposition bench keeps its own dtype): "nll_fp32" and the byte-reduction
    ratios measure quantization alone against a true fp32 oracle, not a
    baseline whose meaning shifts with an unrelated flag."""
    import numpy as np

    from csed_514_project_distributed_training_using_pytorch_tpu.models import (
        lm as lm_mod,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine, Request,
    )

    import jax
    import jax.numpy as jnp

    if model.dtype != jnp.float32:
        model = model.clone(dtype=jnp.float32)

    s = args.seq
    rng = np.random.default_rng(11)
    # Prompt-heavy mix (prefill exercised) + short prompts (decode exercised).
    lens = sorted({s // 8, s // 4, s // 2, (3 * s) // 4})
    specs = []
    for i in range(args.ab_requests):
        p_len = int(rng.choice(lens))
        prompt = rng.integers(0, args.vocab, size=p_len).astype(np.int32)
        new = int(rng.integers(args.ab_new_tokens // 2, args.ab_new_tokens + 1))
        specs.append((prompt, new))

    def run_engine(kv_dtype, quant_policy):
        eng = ContinuousBatchingEngine(
            model, params, num_slots=args.ab_slots,
            prefill_chunk_sizes=tuple(
                int(x) for x in args.curve_chunks.split(",") if x),
            kv_dtype=kv_dtype, quant_policy=quant_policy)
        comps = eng.run([Request(prompt=p, max_new_tokens=n, request_id=i)
                         for i, (p, n) in enumerate(specs)])
        return eng, {c.request.request_id: np.asarray(c.tokens) for c in comps}

    eng_a, toks_a = run_engine("model", "off")
    eng_b, toks_b = run_engine(args.ab_kv_dtype, args.ab_quant_policy)

    # Greedy token-match rate vs the fp32 oracle, over GENERATED positions
    # only (the prompt prefix is teacher-forced on both sides). Positionwise
    # agreement; prefix_match additionally reports agreement up to the first
    # divergence (after which conditioning differs by construction).
    agree = total = prefix_agree = 0
    for i, (p, _) in enumerate(specs):
        a, b = toks_a[i][len(p):], toks_b[i][len(p):]
        n = min(len(a), len(b))
        eq = a[:n] == b[:n]
        agree += int(eq.sum())
        total += n
        div = np.nonzero(~eq)[0]
        prefix_agree += int(div[0]) if len(div) else n
    token_match_rate = agree / total if total else None
    prefix_match_rate = prefix_agree / total if total else None

    # NLL delta through the serving decode path, teacher-forced on oracle
    # greedy streams (real model traffic, not random tokens).
    targets = lm_mod.generate(model, params, jax.random.PRNGKey(2),
                              batch=args.ab_nll_batch, temperature=0.0)
    nll_a = float(lm_mod.decode_nll(model, eng_a.params,
                                    jnp.asarray(targets)))
    nll_b = float(lm_mod.decode_nll(model, eng_b.params, jnp.asarray(targets),
                                    kv_dtype=args.ab_kv_dtype))
    acct_a, acct_b = eng_a.byte_accounting(), eng_b.byte_accounting()
    doc = {
        "metric": "quantized-execution A/B (kv %s, weights %s)"
                  % (args.ab_kv_dtype, args.ab_quant_policy),
        "model_dtype": "float32",  # the oracle is pinned fp32 (see docstring)
        "requests": len(specs),
        "prompt_lens": lens,
        "a": {"kv_dtype": "model", "quant_policy": "off", "bytes": acct_a,
              "trace_count": eng_a.trace_count,
              "prefill_trace_counts": dict(eng_a.prefill_trace_counts)},
        "b": {"kv_dtype": args.ab_kv_dtype,
              "quant_policy": args.ab_quant_policy, "bytes": acct_b,
              "trace_count": eng_b.trace_count,
              "prefill_trace_counts": dict(eng_b.prefill_trace_counts)},
        # The two committed ratios: measured decode bytes/token reduction and
        # the slots-per-chip multiplier under the same HBM budget.
        "decode_bytes_per_token_reduction":
            acct_a["decode_bytes_per_token"] / acct_b["decode_bytes_per_token"],
        "kv_bytes_per_slot_reduction":
            acct_a["kv_bytes_per_slot"] / acct_b["kv_bytes_per_slot"],
        "slots_at_budget_ratio":
            (acct_b["slots_at_budget"] / acct_a["slots_at_budget"]
             if acct_a["slots_at_budget"] else None),
        # The accuracy budget, pinned with explicit bounds.
        "token_match_rate": token_match_rate,
        "prefix_match_rate": prefix_match_rate,
        "token_match_bound": args.ab_match_bound,
        "nll_fp32": nll_a,
        "nll_quant": nll_b,
        "nll_delta": nll_b - nll_a,
        "nll_delta_bound": args.ab_nll_bound,
        "one_program_pins": {
            "decode_trace_count_ok":
                eng_a.trace_count == 1 and eng_b.trace_count == 1,
            "prefill_trace_counts_ok": all(
                v <= 1 for e in (eng_a, eng_b)
                for v in e.prefill_trace_counts.values()),
        },
        "accuracy_ok": (token_match_rate is not None
                        and token_match_rate >= args.ab_match_bound
                        and abs(nll_b - nll_a) <= args.ab_nll_bound),
    }
    return doc


def paged_ab(model, params, args) -> dict:
    """The paged-KV A/B (``bench_results/paged_kv_cpu/``): one seeded mixed
    workload — short interactive requests (~32 total tokens) interleaved with
    near-``seq_len`` prompts — through a contiguous-oracle engine (A) and a
    paged engine (B), reporting (1) greedy token identity (the bitwise
    contract the paged adapters are built on); (2) byte-true residency:
    contiguous charges every slot the full ``[S]`` planes, paged charges the
    page span each request actually reserved, so slots-at-HBM-budget is
    measured from THIS workload's page costs, not a dtype formula; (3) the
    long-prompt latency tails (TTFT/TPOT p50/p95 per side) — the paged layout
    must buy capacity without taxing the requests that DO use full context;
    (4) the compile pins and the pool's own ledger (allocs/frees/refusals)."""
    import time as _time

    import numpy as np

    from csed_514_project_distributed_training_using_pytorch_tpu.serving import (
        ContinuousBatchingEngine, Request,
    )
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.pagepool import (
        pages_for,
    )

    s = args.seq
    chunks = tuple(int(x) for x in args.curve_chunks.split(",") if x)
    rng = np.random.default_rng(13)
    long_len = max(s - args.paged_new_tokens - 1, s // 2)
    specs = []      # (kind, prompt, max_new) — shorts with longs interleaved
    for i in range(args.paged_requests):
        p_len = int(rng.integers(8, 24))
        new = max(32 - p_len + int(rng.integers(0, 8)), 1)
        specs.append(("short",
                      rng.integers(0, args.vocab, size=p_len).astype(np.int32),
                      new))
        if i % max(args.paged_requests // args.paged_long_requests, 1) == 0 \
                and sum(k == "long" for k, _, _ in specs) \
                < args.paged_long_requests:
            specs.append((
                "long",
                rng.integers(0, args.vocab, size=long_len).astype(np.int32),
                args.paged_new_tokens))
    warm = rng.integers(0, args.vocab, size=s - 2).astype(np.int32)

    def run_engine(**layout_kw):
        eng = ContinuousBatchingEngine(
            model, params, num_slots=args.paged_slots,
            prefill_chunk_sizes=chunks, **layout_kw)
        # Compile every chunk size + the decode program off the clock (one
        # request per size plans as exactly one chunk), then wipe the ledger.
        for c in eng.prefill_chunk_sizes:
            eng.run([Request(prompt=warm[:min(c, s - 1)], max_new_tokens=1)])
        eng.run([Request(prompt=warm, max_new_tokens=2)])
        eng.reset_stats()
        reqs = [Request(prompt=p, max_new_tokens=n, request_id=i)
                for i, (_, p, n) in enumerate(specs)]
        t0 = _time.monotonic()
        comps = eng.run(reqs)
        wall = _time.monotonic() - t0
        return eng, {c.request.request_id: c for c in comps}, wall

    eng_a, comps_a, wall_a = run_engine()
    eng_b, comps_b, wall_b = run_engine(kv_layout="paged",
                                        page_size=args.paged_page_size)

    identical = all(
        np.array_equal(comps_a[i].tokens, comps_b[i].tokens)
        for i in range(len(specs)))

    def tails(comps, kind):
        rows = [comps[i] for i, (k, _, _) in enumerate(specs) if k == kind]
        out = {}
        for field in ("ttft_s", "tpot_s"):
            vals = [getattr(c, field) for c in rows
                    if getattr(c, field) is not None]
            out[field] = ({"p50": float(np.percentile(vals, 50)),
                           "p95": float(np.percentile(vals, 95))}
                          if vals else None)
        return out

    acct_a, acct_b = eng_a.byte_accounting(), eng_b.byte_accounting()
    ps = eng_b.page_size
    page_bytes = acct_b["page_bytes"]
    # Slots at a fixed HBM budget, measured from THIS workload: contiguous
    # charges kv_bytes_per_slot regardless of context; paged charges the mean
    # page reservation of the mix (each request's ceil(total/ps) pages).
    budget = float(args.paged_hbm_budget
                   or args.paged_slots * acct_a["kv_bytes_per_slot"])
    req_pages = [pages_for(len(p) + n, ps) for _, p, n in specs]
    mean_req_bytes = sum(req_pages) / len(req_pages) * page_bytes
    slots_a = int(budget // acct_a["kv_bytes_per_slot"])
    slots_b = int(budget // mean_req_bytes)
    t_a, t_b = tails(comps_a, "long"), tails(comps_b, "long")
    ttft_ratio = (t_b["ttft_s"]["p95"] / t_a["ttft_s"]["p95"]
                  if t_a.get("ttft_s") and t_b.get("ttft_s")
                  and t_a["ttft_s"]["p95"] else None)
    gen_tokens = sum(c.new_tokens for c in comps_a.values())
    doc = {
        "metric": "paged-KV A/B (page_size %d, %d short + %d long requests)"
                  % (ps, sum(k == "short" for k, _, _ in specs),
                     sum(k == "long" for k, _, _ in specs)),
        "requests": len(specs),
        "seq_len": s,
        "long_prompt_len": long_len,
        "token_identical": bool(identical),
        "a": {"kv_layout": "contiguous", "bytes": acct_a, "wall_s": wall_a,
              "tokens_per_s": gen_tokens / wall_a if wall_a else None,
              "trace_count": eng_a.trace_count,
              "prefill_trace_counts": dict(eng_a.prefill_trace_counts),
              "long": t_a, "short": tails(comps_a, "short")},
        "b": {"kv_layout": "paged", "bytes": acct_b, "wall_s": wall_b,
              "tokens_per_s": gen_tokens / wall_b if wall_b else None,
              "trace_count": eng_b.trace_count,
              "prefill_trace_counts": dict(eng_b.prefill_trace_counts),
              "long": t_b, "short": tails(comps_b, "short"),
              "kv_pages": eng_b.page_stats()},
        # The committed capacity claim: how many of THIS mix's requests fit
        # the same HBM budget under each layout.
        "hbm_budget_bytes": budget,
        "mean_request_pages": sum(req_pages) / len(req_pages),
        "page_bytes": page_bytes,
        "slots_at_budget_contiguous": slots_a,
        "slots_at_budget_paged": slots_b,
        "slots_at_budget_ratio": slots_b / slots_a if slots_a else None,
        "slots_ratio_bound": args.paged_slots_bound,
        "long_ttft_p95_ratio": ttft_ratio,
        "long_ttft_bound": args.paged_ttft_bound,
        "capacity_ok": (slots_a > 0
                        and slots_b / slots_a >= args.paged_slots_bound),
        "latency_ok": (ttft_ratio is not None
                       and ttft_ratio <= args.paged_ttft_bound),
        "accounting": ("byte-true: per-slot/page bytes from live buffers; "
                       "page costs from the engine's own pages_for"),
    }
    return doc


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=16)
    p.add_argument("--seq", type=int, default=784)
    p.add_argument("--gen-batch", type=int, default=8)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--ttft-curve", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="add the serving TTFT-vs-prompt-length curve, chunked "
                        "prefill on vs off, with the prefill/decode wall split")
    p.add_argument("--curve-prompt-lens", default="64,256,512,768",
                   help="prompt lengths for --ttft-curve (clipped to < --seq)")
    p.add_argument("--curve-chunks", default="32,128,512",
                   help="prefill chunk-size set for the ON side of the curve")
    p.add_argument("--curve-new-tokens", type=int, default=8)
    p.add_argument("--checkpoint", default="",
                   help="TrainState or params msgpack from train.lm — real "
                        "weights for the accuracy-budget side of --quant-ab "
                        "(default: seeded random init)")
    p.add_argument("--quant-ab", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="run the quantized-execution A/B (fp32 oracle vs "
                        "--ab-kv-dtype/--ab-quant-policy engine): measured "
                        "bytes, accuracy budget, compile pins")
    p.add_argument("--ab-kv-dtype", default="int8",
                   choices=("fp32", "bf16", "int8", "fp8"))
    p.add_argument("--ab-quant-policy", default="w8",
                   choices=("off", "w8", "w8a8"))
    p.add_argument("--ab-requests", type=int, default=8)
    p.add_argument("--ab-new-tokens", type=int, default=16)
    p.add_argument("--ab-slots", type=int, default=4)
    p.add_argument("--ab-nll-batch", type=int, default=4)
    p.add_argument("--ab-match-bound", type=float, default=0.98,
                   help="min greedy token-match rate vs the fp32 oracle "
                        "(the documented accuracy budget)")
    p.add_argument("--ab-nll-bound", type=float, default=0.05,
                   help="max |NLL delta| through the quantized decode path")
    p.add_argument("--paged-ab", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="run the paged-KV A/B (contiguous oracle vs "
                        "kv_layout='paged'): token identity, measured "
                        "slots-at-HBM-budget on a mixed short/long workload, "
                        "long-prompt TTFT/TPOT tails, compile pins")
    p.add_argument("--paged-page-size", type=int, default=64)
    p.add_argument("--paged-requests", type=int, default=12,
                   help="short (~32 total tokens) requests in the mix")
    p.add_argument("--paged-long-requests", type=int, default=4,
                   help="near-seq_len prompts interleaved into the mix")
    p.add_argument("--paged-new-tokens", type=int, default=8,
                   help="generated tokens per long request")
    p.add_argument("--paged-slots", type=int, default=4)
    p.add_argument("--paged-hbm-budget", type=float, default=0.0,
                   help="HBM budget (bytes) for the slots-at-budget claim; "
                        "0 = paged_slots contiguous slots' worth")
    p.add_argument("--paged-slots-bound", type=float, default=2.0,
                   help="min paged/contiguous slots-at-budget ratio")
    p.add_argument("--paged-ttft-bound", type=float, default=1.25,
                   help="max long-prompt p95 TTFT ratio (paged/contiguous)")
    p.add_argument("--out", default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from csed_514_project_distributed_training_using_pytorch_tpu.models import lm as lm_mod
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.benchmarks import (
        chained_diff_time, peak_hbm_bytes,
    )

    model = lm_mod.TransformerLM(
        vocab_size=args.vocab + 1, seq_len=args.seq, embed_dim=args.d_model,
        num_layers=args.layers, num_heads=args.heads,
        dtype=jnp.bfloat16 if args.bf16 else jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, args.seq), jnp.int32))["params"]
    if args.checkpoint:
        from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
            checkpoint as ckpt_mod,
        )
        params = ckpt_mod.load_params_or_state(args.checkpoint, params)

    # --- 3. ops per token: the optimized HLO of ONE decode step ---------------
    cache = lm_mod.init_cache(model, args.gen_batch)
    tok = jnp.zeros((args.gen_batch,), jnp.int32)

    def one_step(params, cache, tok):
        cache, logp = lm_mod.decode_step(model, params, cache, tok,
                                         jnp.int32(0), prefix_len=128)
        return cache, logp

    compiled = jax.jit(one_step).lower(params, cache, tok).compile()
    hlo = compiled.as_text()
    # Executable ops = instructions in ENTRY whose opcode launches work on the
    # TensorCore: fusions, custom-calls, copies, convolutions/dots that escaped
    # fusion. Parameter/tuple plumbing is free.
    entry = hlo.split("ENTRY")[-1]
    launched = re.findall(
        r"= \S+ (fusion|custom-call|copy|convolution|dot|all-reduce|"
        r"dynamic-slice|dynamic-update-slice|reduce|transpose|select-and-scatter)",
        entry)
    ops_per_token = len(launched)
    op_kinds = {}
    for kind in launched:
        op_kinds[kind] = op_kinds.get(kind, 0) + 1

    # --- 1. measured per-token seconds (bench_lm's protocol) ------------------
    def gen_chain(n):
        def body(k, _):
            ids = lm_mod.generate(model, params, k, batch=args.gen_batch,
                                  temperature=1.0)
            return jax.random.fold_in(k, jnp.sum(ids)), ()

        def run(k):
            return lax.scan(body, k, None, length=n)[0]

        return jax.jit(run)

    def synced(n):
        compiled = gen_chain(n)
        return lambda: jax.device_get(compiled(jax.random.PRNGKey(3)))

    per_gen, (n1, t1), (n2, t2), converged = chained_diff_time(
        synced, n1=1, grow=4, max_n=64)
    t_token = per_gen / args.seq

    # --- 2. HBM roofline per token (byte-TRUE accounting) ---------------------
    # Bytes come from the ACTUAL buffers, not closed-form dtype assumptions:
    # one cached position's bytes = the real per-slot cache (planes AND any
    # scale planes, at their real itemsize) over seq_len; weights = the real
    # params tree. A quantized run's roofline denominator therefore shrinks
    # exactly as far as its buffers did — the accounting rule the quantized
    # A/B below relies on.
    from csed_514_project_distributed_training_using_pytorch_tpu.ops import (
        quant as quant_ops,
    )

    s = args.seq
    # average static prefix read per step under the segmented scan
    seg = lm_mod.DECODE_SEGMENT
    nseg = -(-s // seg)
    avg_prefix = sum(min((j + 1) * seg, s) * seg for j in range(nseg)) / s
    row_bytes = quant_ops.tree_bytes(lm_mod.init_cache(model, 1)) / s
    cache_bytes = row_bytes * avg_prefix
    weight_bytes = quant_ops.tree_bytes(params)
    bytes_per_token = cache_bytes + weight_bytes / args.gen_batch
    dev = jax.devices()[0]
    hbm = (peak_hbm_bytes(getattr(dev, "device_kind", ""))
           if dev.platform == "tpu" else None)
    t_roofline = (args.gen_batch * bytes_per_token / hbm) if hbm else None

    residual = (t_token - t_roofline) if t_roofline else None
    doc = {
        "metric": "LM decode per-token decomposition (d=%d)" % args.d_model,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "d_model": args.d_model, "layers": args.layers, "heads": args.heads,
        "seq": s, "decode_batch": args.gen_batch,
        "tokens_per_s": round(args.gen_batch * s / per_gen, 1),
        "t_token_s": t_token, "chain_converged": converged,
        "ops_per_token": ops_per_token, "op_kinds": op_kinds,
        "t_roofline_s": t_roofline,
        "hbm_roofline_frac": (round(t_roofline / t_token, 4)
                              if t_roofline else None),
        "residual_s": residual,
        "per_op_overhead_us": (round(1e6 * residual / ops_per_token, 3)
                               if residual is not None else None),
        "attribution": ("residual / ops_per_token is the device's per-op launch "
                        "floor; the tunnel's ~70 ms host tax is cancelled by the "
                        "chained two-point protocol"),
        "accounting": "byte-true: cache/weight bytes summed from live buffers",
    }
    if args.ttft_curve:
        doc["ttft_curve"] = ttft_curve(model, params, args)
    if args.quant_ab:
        doc["quant_ab"] = quant_ab(model, params, args)
    if args.paged_ab:
        doc["paged_ab"] = paged_ab(model, params, args)
    print(json.dumps(doc))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
