"""Network-chaos fleet bench: the committed gray-failure-tolerance artifact.

The scenario DESIGN.md §23 is judged by: a 3-replica echo fleet (jax-free —
the router mechanics ARE the system under test; deterministic tokens make the
oracle exact) runs a seeded two-wave workload through the
``resilience/netfaults.py`` chaos proxy with a **10x wire straggler** on one
replica plus **corrupt / truncate / drop** schedules on the others. Three
legs, one JSON document:

- **oracle** — the same seeded workload, no chaos, no hedging: the
  token-stream reference and the unfaulted TTFT floor;
- **unhedged chaos** — straggler + wire damage with straggler EJECTION armed
  but hedging off: the tail eats the straggler raw (its p99 is the number
  hedging is judged against);
- **hedged chaos** — identical chaos, hedging on: requests stuck behind the
  slow wire get a speculative second copy, first completion wins.

Gates (exit 0 = all pass, 3 = any fail — the non-blocking CI ``chaos-smoke``
job runs ``--quick`` and uploads the summary either way):

1. **zero lost requests** in every leg: every submit resolves ok;
2. **100% token identity** vs the oracle leg — redispatch after wire damage
   and hedge races are schedule changes, never answer changes;
3. **>=1 ejection AND >=1 probe-recovery** in each chaos leg: the straggler
   is detected (``degraded``), sat out, and probed back to ``ready`` once the
   chaos schedule drains — with ZERO process restarts (slow is handled in
   place; the wire faults are typed reconnects, not deaths);
4. **zero orphan traces** in the traced chaos legs;
5. **hedge wins the tail**: hedged p99 TTFT <= ``--hedge-ratio`` x unhedged
   p99 TTFT (default 0.8 — "measurably below"), with >=1 hedge win recorded.

Usage::

    python tools/bench_chaos_fleet.py --out-dir bench_results/chaos_fleet_cpu
    python tools/bench_chaos_fleet.py --quick --out-dir /tmp/chaos
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

PKG = "csed_514_project_distributed_training_using_pytorch_tpu"


def echo_cmd(args) -> list[str]:
    return ["-m", f"{PKG}.serving.replica", "--echo",
            "--num-levels", str(args.num_levels),
            "--seq-len", str(args.seq_len),
            "--num-slots", str(args.num_slots),
            "--max-pending", str(args.max_pending),
            "--echo-delay-s", str(args.echo_delay_s)]


def chaos_spec(args) -> str:
    """The seeded damage schedule. The straggler is the LINK, not the host:
    replica 1's replies each eat ``straggler_ms`` (about 10x the unfaulted
    e2e) for the first ``straggler_count`` messages, then the link heals —
    which is what lets the probe-recovery gate close. Replicas 0 and 2 take
    one corrupt reply and one truncated submit each (typed reconnect +
    ledger-drain replay) plus a dropped connection — deliberately LATER in
    the message schedule than the straggler window, so the hedge A/B
    measures the straggler (the gray failure under test), not a correlated
    all-replicas-down storm (which has its own regression tests)."""
    # Unit budgeting: on the STRAGGLER's serialized pipe, replies coalesce
    # behind each delay (several done lines, one TCP unit), so `count` is
    # small — it must exhaust within wave 1 so the probe finds a healed link.
    # The corrupt/truncate units land mid-wave-1 on the healthy replicas
    # (~one unit per reply there); the drop hits replica 0's SECOND
    # connection — the one the corrupt-triggered reconnect established.
    return (f"delay:replica=1,conn=0,dir=s2c,after=1,ms={args.straggler_ms:g},"
            f"count={args.straggler_count};"
            f"corrupt:replica=0,conn=0,dir=s2c,after=10;"
            f"truncate:replica=2,conn=0,dir=c2s,after=12;"
            f"drop:replica=0,conn=1,dir=s2c,after=6")


def make_workload(args):
    rng = np.random.default_rng(args.seed)
    reqs = []
    for _ in range(args.requests + args.post_requests):
        plen = int(rng.integers(2, 6))
        prompt = rng.integers(0, args.num_levels - 1,
                              size=plen).astype(np.int32)
        reqs.append((prompt, int(rng.integers(3, args.max_new + 1))))
    return reqs


def run_leg(args, reqs, name, *, chaos="", hedge=False, straggler_k=0.0,
            out_dir="", trace=False):
    from csed_514_project_distributed_training_using_pytorch_tpu.serving.router import (
        Router,
    )

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = (f"{repo_root}:{env['PYTHONPATH']}"
                         if env.get("PYTHONPATH") else repo_root)
    tele = os.path.join(out_dir, f"router_{name}.jsonl")
    trace_dir = os.path.join(out_dir, f"trace_{name}") if trace else ""
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
        for stale in os.listdir(trace_dir):   # span files append across runs
            os.unlink(os.path.join(trace_dir, stale))
    if os.path.exists(tele):
        os.unlink(tele)
    router = Router(
        echo_cmd(args), num_replicas=args.replicas,
        heartbeat_dir=os.path.join(out_dir, f"hb_{name}"),
        heartbeat_timeout_s=30.0, backoff_s=0.2,
        telemetry=tele, trace_dir=trace_dir,
        chaos=chaos, chaos_seed=args.seed,
        straggler_k=straggler_k, eject_min_samples=args.eject_min_samples,
        eject_cooldown_s=args.eject_cooldown_s,
        hedge=hedge, hedge_after_s=args.hedge_after_s,
        env=env)
    router.start()
    comps = []
    try:
        if not router.wait_ready(timeout=120):
            raise RuntimeError(f"leg {name}: fleet never came up")
        # Wave 1: the chaos window — paced so the straggler's ledger stays
        # occupied while healthy peers turn over.
        futs = []
        for prompt, max_new in reqs[:args.requests]:
            futs.append(router.submit(prompt, max_new_tokens=max_new,
                                      tenant="paid"))
            time.sleep(args.pace_s)
        comps.extend(f.result(timeout=300) for f in futs)
        if straggler_k > 0:
            # Wait for the eject->probe cycle: the straggler's link healed
            # when its delay schedule ran out, so the cooldown expiry
            # re-opens it. Bounded waits — a missed ejection fails its gate
            # loudly rather than stalling the leg.
            deadline = time.monotonic() + 15
            while (router.replicas[1].ejections < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            deadline = time.monotonic() + args.eject_cooldown_s + 10
            while (router.replicas[1].probes < router.replicas[1].ejections
                   and time.monotonic() < deadline):
                time.sleep(0.05)
        # Wave 2: post-recovery traffic — proves the probed replica serves.
        futs = [router.submit(p, max_new_tokens=n, tenant="paid")
                for p, n in reqs[args.requests:]]
        comps.extend(f.result(timeout=300) for f in futs)
    finally:
        summary = router.stop(timeout=120)
    return comps, summary, trace_dir


def pcts(vals):
    from csed_514_project_distributed_training_using_pytorch_tpu.utils.jsonl import (
        percentiles,
    )

    return percentiles([v for v in vals if v is not None], qs=(50, 95, 99))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--out-dir", default="bench_results/chaos_fleet_cpu")
    p.add_argument("--quick", action="store_true",
                   help="CI sizing: fewer requests, same gates, laxer ratio")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--requests", type=int, default=45,
                   help="wave-1 (chaos window) requests")
    p.add_argument("--post-requests", type=int, default=12,
                   help="wave-2 (post-recovery) requests")
    p.add_argument("--pace-s", type=float, default=0.03,
                   help="wave-1 inter-arrival pacing")
    p.add_argument("--num-levels", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--num-slots", type=int, default=4)
    p.add_argument("--max-pending", type=int, default=8)
    p.add_argument("--max-new", type=int, default=6)
    p.add_argument("--echo-delay-s", type=float, default=0.02,
                   help="per-token replica compute (sets the unfaulted floor)")
    p.add_argument("--straggler-ms", type=float, default=0.0,
                   help="per-reply wire delay on the straggler (0 = 10x the "
                        "unfaulted per-request wall, derived from "
                        "echo-delay-s x max-new)")
    p.add_argument("--straggler-count", type=int, default=4,
                   help="delayed reply UNITS before the straggler's link "
                        "heals (few but serial: each holds the pipe for "
                        "straggler-ms, and replies coalesce behind it)")
    p.add_argument("--straggler-k", type=float, default=3.0)
    p.add_argument("--eject-min-samples", type=int, default=3,
                   help="low on purpose: the straggler's delayed replies "
                        "COALESCE on the slow link (several done lines, one "
                        "TCP unit), so it yields few — but huge — samples")
    p.add_argument("--eject-cooldown-s", type=float, default=1.5)
    p.add_argument("--hedge-after-s", type=float, default=0.0,
                   help="hedge deadline (0 = 3x the unfaulted per-request "
                        "wall — far above normal, far below the straggler)")
    p.add_argument("--hedge-ratio", type=float, default=0.8,
                   help="gate: hedged p99 TTFT <= this x unhedged p99")
    args = p.parse_args(argv)
    if args.quick:
        args.requests = 27
        args.post_requests = 8
        if args.hedge_ratio == 0.8:
            args.hedge_ratio = 0.9       # smoke trip wire on a noisy runner
    # The unfaulted per-request wall: tokens x per-token sleep. The straggler
    # multiplies it ~10x at the WIRE; the hedge deadline sits 3x above normal.
    base_wall = args.echo_delay_s * args.max_new
    if args.straggler_ms <= 0:
        args.straggler_ms = 10 * base_wall * 1000.0
    if args.hedge_after_s <= 0:
        args.hedge_after_s = 3 * base_wall
    os.makedirs(args.out_dir, exist_ok=True)
    spec = chaos_spec(args)
    reqs = make_workload(args)
    n_total = len(reqs)
    print(f"workload: {n_total} requests ({args.requests} through the chaos "
          f"window), straggler {args.straggler_ms:.0f}ms/reply x "
          f"{args.straggler_count}, hedge deadline {args.hedge_after_s:.2f}s")
    print(f"chaos spec: {spec}")

    print("== leg 1/3: oracle (no chaos, no hedging)")
    oracle_comps, oracle_sum, _ = run_leg(args, reqs, "oracle",
                                          out_dir=args.out_dir)
    oracle_tokens = {c.request_id: c.tokens.tolist() for c in oracle_comps}
    oracle_ttft = pcts([c.ttft_s for c in oracle_comps])
    print(f"   {oracle_sum['ok']}/{n_total} ok, ttft p99 "
          f"{oracle_ttft['p99'] * 1e3:.0f}ms")

    from csed_514_project_distributed_training_using_pytorch_tpu.utils import (
        trace as trace_mod,
    )

    legs = {}
    for name, hedge in (("unhedged", False), ("hedged", True)):
        print(f"== leg {'2' if not hedge else '3'}/3: chaos, "
              f"hedging {'on' if hedge else 'off'} (traced)")
        comps, summ, trace_dir = run_leg(
            args, reqs, name, chaos=spec, hedge=hedge,
            straggler_k=args.straggler_k, out_dir=args.out_dir, trace=True)
        spans, _ = trace_mod.read_spans([trace_dir])
        tsum = trace_mod.summarize_traces(spans)
        mismatched = sum(
            c.tokens.tolist() != oracle_tokens[c.request_id] for c in comps)
        ttft = pcts([c.ttft_s for c in comps])
        legs[name] = {
            "ok": sum(c.ok for c in comps), "resolved": len(comps),
            "offered": n_total, "mismatched": mismatched,
            "ttft_s": ttft, "e2e_s": pcts([c.e2e_s for c in comps]),
            "ejections": summ["ejections"], "probes": summ["probes"],
            "hedges": summ["hedges"], "hedge_wins": summ["hedge_wins"],
            "hedge_win_rate": summ["hedge_win_rate"],
            "wire_corrupt": summ["wire_corrupt"],
            "redispatches": summ["redispatches"],
            "duplicates": summ["duplicates"],
            "replica_restarts": summ["replica_restarts"],
            "straggler_state": summ["per_replica"][1]["state"],
            "trace": {"traces": tsum["traces"], "orphans": tsum["orphans"],
                      "hedged": tsum["hedged"],
                      "redispatched": tsum["redispatched"]},
        }
        print(f"   {legs[name]['ok']}/{n_total} ok, ttft p99 "
              f"{ttft['p99'] * 1e3:.0f}ms, {summ['ejections']} ejection(s), "
              f"{summ['probes']} probe(s), {summ['hedges']} hedge(s) "
              f"({summ['hedge_wins']} won), {summ['wire_corrupt']} typed "
              f"wire fault(s), {summ['redispatches']} redispatch(es), "
              f"{tsum['orphans']} orphan trace(s), {mismatched} token "
              f"mismatch(es)")

    ratio = (legs["hedged"]["ttft_s"]["p99"]
             / legs["unhedged"]["ttft_s"]["p99"])
    gates = {
        "zero_lost": {
            "resolved": {n: legs[n]["resolved"] for n in legs},
            "ok": {n: legs[n]["ok"] for n in legs},
            "pass": all(legs[n]["ok"] == legs[n]["resolved"] == n_total
                        for n in legs) and oracle_sum["ok"] == n_total},
        "token_identity": {
            "mismatched": {n: legs[n]["mismatched"] for n in legs},
            "pass": all(legs[n]["mismatched"] == 0 for n in legs)},
        "eject_and_recover": {
            "ejections": {n: legs[n]["ejections"] for n in legs},
            "probes": {n: legs[n]["probes"] for n in legs},
            "straggler_state": {n: legs[n]["straggler_state"] for n in legs},
            "replica_restarts": {n: legs[n]["replica_restarts"] for n in legs},
            # Ejected, probed back, and the process never restarted: slow was
            # handled in place, distinct from hang. The final state is
            # recorded but not gated — a residual delayed unit reaching a
            # wave-2 reply can legitimately start a SECOND eject cycle that
            # is mid-cooldown at stop time (the detector doing its job).
            "pass": all(legs[n]["ejections"] >= 1 and legs[n]["probes"] >= 1
                        and legs[n]["replica_restarts"] == 0 for n in legs)},
        "typed_wire_faults": {
            "wire_corrupt": {n: legs[n]["wire_corrupt"] for n in legs},
            "pass": all(legs[n]["wire_corrupt"] >= 1 for n in legs)},
        "zero_orphans": {
            "orphans": {n: legs[n]["trace"]["orphans"] for n in legs},
            "pass": all(legs[n]["trace"]["orphans"] == 0 for n in legs)},
        "hedge_wins_the_tail": {
            "unhedged_p99_s": legs["unhedged"]["ttft_s"]["p99"],
            "hedged_p99_s": legs["hedged"]["ttft_s"]["p99"],
            "oracle_p99_s": oracle_ttft["p99"],
            "ratio": ratio, "limit": args.hedge_ratio,
            "hedge_wins": legs["hedged"]["hedge_wins"],
            "pass": (ratio <= args.hedge_ratio
                     and legs["hedged"]["hedge_wins"] >= 1)},
    }
    doc = {
        "bench": "chaos_fleet",
        "config": {k: getattr(args, k) for k in
                   ("replicas", "requests", "post_requests", "pace_s",
                    "echo_delay_s", "straggler_ms", "straggler_count",
                    "straggler_k", "eject_min_samples", "eject_cooldown_s",
                    "hedge_after_s", "seed", "quick")},
        "chaos_spec": spec,
        "oracle": {"ok": oracle_sum["ok"], "ttft_s": oracle_ttft},
        "legs": legs,
        "gates": gates,
        "pass": all(g["pass"] for g in gates.values()),
    }
    out = os.path.join(args.out_dir, "summary.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1, default=float)
    print(f"summary -> {out}  ({'PASS' if doc['pass'] else 'FAIL'})")
    for name, g in gates.items():
        print(f"   gate {name}: {'ok' if g['pass'] else 'FAIL'} "
              f"{ {k: v for k, v in g.items() if k != 'pass'} }")
    return 0 if doc["pass"] else 3


if __name__ == "__main__":
    sys.exit(main())
